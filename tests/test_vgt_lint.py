"""vgtlint suite tests (ISSUE 14): the tier-1 repo gate (the whole
suite must pass over the repo with an EMPTY baseline), positive +
negative fixture snippets per checker, suppression / baseline
round-trips, and a seeded-mutation test that reintroduces the PR-5
historical bug shape (a ``_readback_lock``-guarded field mutated bare)
into a copy of the real engine_core.py and proves the linter flags it.
"""

import json
import os
import shutil
import textwrap
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from vgate_tpu.analysis import runner as lint_runner  # noqa: E402
from vgate_tpu.analysis.checkers import (  # noqa: E402
    all_checkers,
    checkers_by_name,
)
from vgate_tpu.analysis.checkers.async_blocking import (  # noqa: E402
    AsyncBlockingChecker,
)
from vgate_tpu.analysis.checkers.drift import (  # noqa: E402
    DefinitionDriftChecker,
)
from vgate_tpu.analysis.checkers.error_taxonomy import (  # noqa: E402
    ErrorTaxonomyChecker,
)
from vgate_tpu.analysis.checkers.jit_purity import (  # noqa: E402
    JitPurityChecker,
)
from vgate_tpu.analysis.checkers.threads import (  # noqa: E402
    ThreadDisciplineChecker,
)
from vgate_tpu.analysis.core import (  # noqa: E402
    Baseline,
    Project,
    parse_suppressions,
)


def _write(root, relpath, text):
    path = os.path.join(root, relpath)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write(textwrap.dedent(text))
    return path


def _run(root, checker_names, only=None, baseline=None):
    by_name = checkers_by_name()
    return lint_runner.run(
        root,
        [by_name[n] for n in checker_names],
        only=only,
        baseline=baseline,
    )


def _rules(result):
    return sorted({v.rule for v in result.violations})


# ---------------------------------------------------------------- repo gate


def test_repo_is_clean_with_empty_baseline():
    """THE acceptance gate: every checker over the whole repo, no
    baseline entries, zero findings — all original true positives were
    fixed or carry inline justifications."""
    t0 = time.monotonic()
    baseline = Baseline.load(
        os.path.join(REPO_ROOT, lint_runner.DEFAULT_BASELINE)
    )
    assert baseline.entries == {}, (
        "the repo baseline must stay empty — fix or inline-justify "
        f"instead of baselining: {sorted(baseline.entries)}"
    )
    result = lint_runner.run(REPO_ROOT, all_checkers(), baseline=baseline)
    assert result.ok, "vgt-lint findings:\n" + "\n".join(
        v.render() for v in result.violations
    )
    assert len(result.checkers_run) == 9
    assert time.monotonic() - t0 < 30.0


def test_cli_smoke(capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "vgt_lint_cli", os.path.join(REPO_ROOT, "scripts", "vgt_lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--list-checkers"]) == 0
    out = capsys.readouterr().out
    for name in (
        "thread-discipline",
        "lock-order",
        "obligations",
        "epoch-guard",
        "jit-purity",
        "error-taxonomy",
        "definition-drift",
        "async-blocking",
        "metrics",
    ):
        assert name in out
    assert mod.main(["--checkers", "nope"]) == 2


# ------------------------------------------------ this-PR fix regressions


def test_errors_taxonomy_complete_at_runtime():
    """Regression for the E002/E003 haul: every taxonomy class carries
    a machine-readable `reason`, and every declared sdk_twin names a
    real vgate_tpu_client class — checked on the LIVE objects, not the
    AST, so a refactor that breaks inheritance fails here too."""
    import inspect
    import sys

    from vgate_tpu import errors

    sys.path.insert(0, os.path.join(REPO_ROOT, "vgate_tpu_client"))
    from vgate_tpu_client import exceptions as sdk

    classes = [
        obj
        for _, obj in inspect.getmembers(errors, inspect.isclass)
        if obj.__module__ == "vgate_tpu.errors"
        and issubclass(obj, BaseException)
    ]
    assert len(classes) >= 15
    for cls in classes:
        assert isinstance(getattr(cls, "reason", None), str), (
            f"{cls.__name__} lost its machine-readable reason"
        )
        twin = getattr(cls, "sdk_twin", None)
        if twin is not None:
            assert hasattr(sdk, twin), (
                f"{cls.__name__}.sdk_twin = {twin!r} does not exist "
                "in vgate_tpu_client"
            )
    # the never-serialized internals are the only twin-less classes
    twinless = {
        c.__name__ for c in classes if getattr(c, "sdk_twin", None) is None
    }
    assert twinless == {"EngineStalledError", "ClientDisconnectError"}


def test_supervisor_and_dp_lock_annotations_live():
    """Regression for the supervisor/dp T003 fixes: the lock-guard
    registries stay declared, and the requires_lock annotations stay
    on the methods the checker verifies callers against."""
    from vgate_tpu.analysis.annotations import required_locks
    from vgate_tpu.runtime import dp_engine, supervisor

    assert supervisor.VGT_LOCK_GUARDS["_pending_resume"] == "_lock"
    assert supervisor.VGT_LOCK_GUARDS["_quarantine"] == "_lock"
    assert required_locks(
        supervisor.EngineSupervisor._update_quarantine_locked
    ) == ("_lock",)
    assert dp_engine.VGT_LOCK_GUARDS["_draining"] == "_topology_lock"
    assert dp_engine.VGT_LOCK_GUARDS["replicas"] == "_topology_lock"
    assert required_locks(
        dp_engine.ReplicatedEngine._maybe_rebuild
    ) == ("_topology_lock",)
    assert required_locks(
        dp_engine.ReplicatedEngine._sweep_locked
    ) == ("_topology_lock",)


def test_engine_core_annotations_live():
    """The engine loop root and a sample of the hot-path methods keep
    their engine-thread annotations (the seeded-mutation test proves
    the checker fires; this proves the contract stays declared)."""
    from vgate_tpu.analysis.annotations import (
        is_engine_thread_only,
        is_engine_thread_root,
    )
    from vgate_tpu.runtime.engine_core import EngineCore

    assert is_engine_thread_root(EngineCore._loop)
    for name in (
        "_tick",
        "_admit_and_prefill",
        "_process_chunks",
        "_drain_submissions",
        "_drain_abort_requests",
        "_process_evacuations",
        "_maybe_finish",
    ):
        assert is_engine_thread_only(getattr(EngineCore, name)), name


# ------------------------------------------------------- thread-discipline


@pytest.fixture
def thread_fixture(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/runtime/core.py",
        '''
        from vgate_tpu.analysis.annotations import (
            engine_thread_only, engine_thread_root, requires_lock,
        )

        VGT_LOCK_GUARDS = {"_checkpointed": "_readback_lock"}
        VGT_COMPONENTS = {"sched": "Sched"}

        class Sched:
            @engine_thread_only
            def add(self, seq):
                pass

            def has_work(self):
                return False

        class Core:
            def __init__(self):
                self._readback_lock = object()
                self._checkpointed = []
                self.sched = Sched()

            @engine_thread_only
            def _tick(self):
                self._maybe_finish()
                self.sched.add(None)

            @engine_thread_only
            def _maybe_finish(self):
                pass

            @engine_thread_root
            def _loop(self):
                self._tick()

            def cross_thread(self):
                self._tick()            # T001 (same class)
                self.sched.add(None)    # T001 (via VGT_COMPONENTS)

            @requires_lock("_readback_lock")
            def _fold(self):
                self._checkpointed = []      # ok: annotated holder

            def good(self):
                with self._readback_lock:
                    self._fold()
                    self._checkpointed.append(1)

            def bounded(self):
                ok = self._readback_lock.acquire(timeout=5)
                try:
                    self._checkpointed = []  # ok: bounded-acquire idiom
                finally:
                    if ok:
                        pass

            def bad(self):
                self._fold()                 # T002
                self._checkpointed = []      # T003 (rebind)
                self._checkpointed.append(2)  # T003 (mutator call)
        ''',
    )
    return str(tmp_path)


def test_thread_discipline_positive_negative(thread_fixture):
    result = _run(thread_fixture, ["thread-discipline"])
    by_rule = {}
    for v in result.violations:
        by_rule.setdefault(v.rule, []).append(v)
    assert len(by_rule.get("T001", [])) == 2
    assert len(by_rule.get("T002", [])) == 1
    assert len(by_rule.get("T003", [])) == 2
    # the compliant call sites produced nothing else
    assert set(by_rule) == {"T001", "T002", "T003"}
    callers = {v.symbol for v in by_rule["T001"]}
    assert callers == {
        "Core.cross_thread->Core._tick",
        "Core.cross_thread->Sched.add",
    }


def test_thread_discipline_registry_typo(tmp_path):
    """T004 checks AST attribute usage, not raw text: a lock shared
    by several fields (so its name appears many times in the registry
    literal) still fires when nothing actually accesses it, and a
    typo'd FIELD key is flagged too (either typo silently disables
    the guard)."""
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        """
        import threading

        VGT_LOCK_GUARDS = {
            "_a": "_topoIogy_lock",   # typo'd lock, shared by 2 fields
            "_b": "_topoIogy_lock",
            "_typod_field": "_real_lock",
        }

        class C:
            def __init__(self):
                self._real_lock = threading.Lock()
                self._a = []
                self._b = []
        """,
    )
    result = _run(str(tmp_path), ["thread-discipline"])
    assert [v.rule for v in result.violations] == ["T004"] * 3
    symbols = {v.symbol for v in result.violations}
    assert symbols == {
        "VGT_LOCK_GUARDS._a:lock",
        "VGT_LOCK_GUARDS._b:lock",
        "VGT_LOCK_GUARDS._typod_field:field",
    }


def test_seeded_mutation_real_engine_core(tmp_path):
    """Reintroduce the PR-5 historical bug shape into a COPY of the
    real engine_core.py: a ``_readback_lock``-guarded field mutated
    bare.  The unmutated copy must lint clean (proving the annotations
    in tree are coherent); the mutated copy must fire T003 on exactly
    the seeded method."""
    dst = os.path.join(tmp_path, "vgate_tpu", "runtime", "engine_core.py")
    os.makedirs(os.path.dirname(dst))
    shutil.copy(
        os.path.join(REPO_ROOT, "vgate_tpu", "runtime", "engine_core.py"),
        dst,
    )
    clean = _run(str(tmp_path), ["thread-discipline"])
    assert clean.ok, [v.render() for v in clean.violations]

    with open(dst, "a") as fh:
        fh.write(
            "\n\ndef _seeded_mutation(self):\n"
            "    self._checkpointed = []\n"
        )
    mutated = _run(str(tmp_path), ["thread-discipline"])
    assert [v.rule for v in mutated.violations] == ["T003"]
    v = mutated.violations[0]
    assert "_checkpointed" in v.message
    assert "_readback_lock" in v.message
    assert v.symbol == "_seeded_mutation._checkpointed"


# ------------------------------------------------------------- jit-purity


def test_jit_purity_fixtures(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/ops/k.py",
        """
        import time, random, functools, jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def bad(n, x):
            t = time.time()                 # J001
            r = random.random()             # J002
            for k in {1, 2}:                # J003
                x = x + k
            print("traced")                 # J004
            return x * t * r

        def wrapped(x):
            return time.perf_counter()      # J001 (jit-wrapped below)
        wrapped = jax.jit(wrapped)

        def host_side(x):
            # identical calls OUTSIDE jit are fine
            time.time(); random.random(); print(x)
            for k in {1, 2}:
                x += k
            return x

        @jax.jit
        def clean(x):
            for k in sorted({1, 2}):        # deterministic: ok
                x = x + k
            return x
        """,
    )
    result = _run(str(tmp_path), ["jit-purity"])
    assert _rules(result) == ["J001", "J002", "J003", "J004"]
    assert len(result.violations) == 5
    assert all("host_side" not in v.symbol for v in result.violations)
    assert all("clean" not in v.symbol for v in result.violations)


# ---------------------------------------------------------- async-blocking


def test_async_blocking_fixtures(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/server/h.py",
        """
        import asyncio, subprocess, threading, time
        import requests

        _lock = threading.Lock()

        async def bad(req):
            time.sleep(1)                   # A001
            requests.get("http://x")        # A002
            _lock.acquire()                 # A003
            subprocess.run(["ls"])          # A004

        async def good(req):
            await asyncio.sleep(0)
            alock = asyncio.Lock()
            await alock.acquire()           # awaited: fine
            loop = asyncio.get_event_loop()
            # references (not calls) may be shipped to an executor
            await loop.run_in_executor(None, time.sleep, 1)

            def helper():
                time.sleep(5)               # nested sync def: excluded

            return helper

        def sync_path():
            time.sleep(1)                   # not async: out of scope
        """,
    )
    result = _run(str(tmp_path), ["async-blocking"])
    assert _rules(result) == ["A001", "A002", "A003", "A004"]
    assert {v.symbol.split(":")[0] for v in result.violations} == {"bad"}


# --------------------------------------------------------- definition-drift


@pytest.fixture
def drift_fixture(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/config.py",
        """
        from pydantic import BaseModel

        class ServerConfig(BaseModel):
            port: int = 8000
            secret_knob: float = 1.0   # D002: nowhere documented

        class VGTConfig(BaseModel):
            server: ServerConfig = None
        """,
    )
    _write(
        tmp_path,
        "config.yaml",
        """
        server:
          port: 8000
          ghost: true   # D001: no model field
        """,
    )
    _write(tmp_path, "docs/operations.md", "# knobs\n\nport only.\n")
    _write(
        tmp_path,
        "vgate_tpu/admission.py",
        'TIERS = ("interactive", "standard", "batch")\n',
    )
    _write(
        tmp_path,
        "vgate_tpu/other.py",
        'COPY = ("interactive", "standard", "batch")  # D003\n',
    )
    _write(
        tmp_path,
        "vgate_tpu/observability/roofline.py",
        "DEVICE_PEAKS = {}\n",
    )
    _write(tmp_path, "benchmarks/rogue.py", "DEVICE_PEAKS = {}  # D004\n")
    _write(
        tmp_path,
        "scripts/_drill_lib.sh",
        'declare -A VGT_DRILL_PORTS=( [x]=8731 )\n',
    )
    _write(tmp_path, "scripts/x_check.sh", 'PORT=8731  # D005\n')
    return str(tmp_path)


def test_definition_drift_fixtures(drift_fixture):
    result = _run(drift_fixture, ["definition-drift"])
    assert _rules(result) == ["D001", "D002", "D003", "D004", "D005"]
    d1 = [v for v in result.violations if v.rule == "D001"]
    assert d1[0].symbol == "server.ghost"
    d2 = [v for v in result.violations if v.rule == "D002"]
    assert d2[0].symbol == "server.secret_knob"


def test_drift_accepts_commented_yaml_knob(drift_fixture):
    # the repo convention: a commented `# knob: value` line documents
    # an optional knob
    with open(os.path.join(drift_fixture, "config.yaml"), "a") as fh:
        fh.write("  # secret_knob: 2.0\n")
    result = _run(drift_fixture, ["definition-drift"])
    assert "D002" not in _rules(result)


# ---------------------------------------------------------- error-taxonomy


@pytest.fixture
def taxonomy_fixture(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/errors.py",
        '''
        class GoodError(RuntimeError):
            reason = "good"
            sdk_twin = "ServerError"

        class ChildError(GoodError):
            """inherits reason + sdk_twin; mapped via ancestor"""

        class OrphanError(RuntimeError):
            """E001+E002+E003+E004: nothing declared anywhere"""

        class BadTwinError(RuntimeError):
            reason = "bad_twin"
            sdk_twin = "DoesNotExist"
        ''',
    )
    _write(
        tmp_path,
        "vgate_tpu/server/app.py",
        """
        def handler():
            try:
                pass
            except GoodError:
                pass
            except BadTwinError:
                pass
        """,
    )
    _write(
        tmp_path,
        "vgate_tpu_client/vgate_tpu_client/exceptions.py",
        """
        class VGTError(Exception):
            pass

        class ServerError(VGTError):
            pass
        """,
    )
    _write(
        tmp_path,
        "docs/operations.md",
        "GoodError, ChildError and BadTwinError are documented.\n",
    )
    return str(tmp_path)


def test_error_taxonomy_fixtures(taxonomy_fixture):
    result = _run(taxonomy_fixture, ["error-taxonomy"])
    by_symbol = {}
    for v in result.violations:
        by_symbol.setdefault(v.symbol, set()).add(v.rule)
    # complete classes (own or inherited declarations) are silent
    assert "GoodError" not in by_symbol
    assert "ChildError" not in by_symbol
    assert by_symbol["OrphanError"] == {"E001", "E002", "E003", "E004"}
    # declared twin that does not exist in the SDK is still E003
    assert by_symbol["BadTwinError"] == {"E003"}


# ------------------------------------------------- suppressions + baseline


def test_suppression_requires_justification(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/server/h.py",
        """
        import time

        async def a(req):
            time.sleep(1)  # vgt-lint: disable=async-blocking -- drill-only endpoint, loop idle by design
            time.sleep(2)  # vgt-lint: disable=async-blocking
        """,
    )
    result = _run(str(tmp_path), ["async-blocking"])
    # line 1: justified -> suppressed.  line 2: unjustified -> BOTH the
    # original finding and the S001 meta-finding surface
    assert result.suppressed == 1
    assert _rules(result) == ["A001", "S001"]


def test_suppression_comment_above(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/server/h.py",
        """
        import time

        async def a(req):
            # vgt-lint: disable=async-blocking -- warmup path, loop not yet serving
            time.sleep(1)
        """,
    )
    result = _run(str(tmp_path), ["async-blocking"])
    assert result.ok and result.suppressed == 1


def test_parse_suppressions():
    sups = parse_suppressions(
        [
            "x = 1  # vgt-lint: disable=a,b -- because reasons",
            "y = 2",
            "# vgt-lint: disable=c",
        ]
    )
    assert sups[0].checkers == ("a", "b")
    assert sups[0].justification == "because reasons"
    # inline (shares the line with code): covers its own line only
    assert sups[0].covers("a", 1) and sups[0].covers("b", 1)
    assert not sups[0].covers("a", 2)
    # comment-only line: covers itself and the statement below
    assert sups[1].justification == ""
    assert sups[1].covers("c", 3) and sups[1].covers("c", 4)


def test_baseline_round_trip(tmp_path):
    root = tmp_path / "proj"
    _write(
        str(root),
        "vgate_tpu/server/h.py",
        """
        import time

        async def a(req):
            time.sleep(1)
        """,
    )
    dirty = _run(str(root), ["async-blocking"])
    assert not dirty.ok
    # adopt with justification -> clean
    entries = {
        v.fingerprint: "legacy drill endpoint; tracked in ISSUE-99"
        for v in dirty.violations
    }
    path = str(tmp_path / "baseline.json")
    Baseline(entries).save(path)
    adopted = _run(
        str(root), ["async-blocking"], baseline=Baseline.load(path)
    )
    assert adopted.ok

    # a TODO justification counts as unjustified (B001)
    Baseline(
        {fp: "TODO: justify or fix" for fp in entries}
    ).save(path)
    todo = _run(
        str(root), ["async-blocking"], baseline=Baseline.load(path)
    )
    assert _rules(todo) == ["B001"]

    # fixing the finding makes the entry stale (B002): the baseline
    # may only shrink
    _write(str(root), "vgate_tpu/server/h.py", "import time\n")
    stale = _run(
        str(root),
        ["async-blocking"],
        baseline=Baseline(entries),
    )
    assert _rules(stale) == ["B002"]


def test_fingerprints_are_line_number_free(tmp_path):
    src = """
    import time

    async def a(req):
        time.sleep(1)
    """
    _write(str(tmp_path), "vgate_tpu/server/h.py", src)
    fp1 = _run(str(tmp_path), ["async-blocking"]).violations[0].fingerprint
    _write(
        str(tmp_path),
        "vgate_tpu/server/h.py",
        "# a new comment shifts every line\n" + textwrap.dedent(src),
    )
    fp2 = _run(str(tmp_path), ["async-blocking"]).violations[0].fingerprint
    assert fp1 == fp2


def test_error_taxonomy_word_boundary(tmp_path):
    """A class whose name is a prefix of a sibling must not be
    vacuously 'mapped'/'documented' by the sibling's mentions."""
    _write(
        tmp_path,
        "vgate_tpu/errors.py",
        '''
        class MigrationError(RuntimeError):
            reason = "migration_error"
            sdk_twin = "ServerError"

        class MigrationRefusedError(MigrationError):
            """only THIS one is referenced in app/docs"""
        ''',
    )
    _write(
        tmp_path,
        "vgate_tpu/server/app.py",
        "def h():\n    try:\n        pass\n"
        "    except MigrationRefusedError:\n        pass\n",
    )
    _write(
        tmp_path,
        "vgate_tpu_client/vgate_tpu_client/exceptions.py",
        "class ServerError(Exception):\n    pass\n",
    )
    _write(
        tmp_path, "docs/operations.md", "MigrationRefusedError only.\n"
    )
    result = _run(str(tmp_path), ["error-taxonomy"])
    by_symbol = {}
    for v in result.violations:
        by_symbol.setdefault(v.symbol, set()).add(v.rule)
    # the base class is neither mapped nor documented on its own
    assert by_symbol.get("MigrationError") == {"E001", "E004"}
    # the child is mapped directly and documented
    assert "MigrationRefusedError" not in by_symbol


def test_drift_common_word_knob_not_vacuously_documented(tmp_path):
    """A knob named with a common word (`enabled`) must not count as
    documented just because the word appears in docs prose; the
    dotted path does count."""
    _write(
        tmp_path,
        "vgate_tpu/config.py",
        """
        from pydantic import BaseModel

        class FooConfig(BaseModel):
            enabled: bool = False

        class VGTConfig(BaseModel):
            foo: FooConfig = None
        """,
    )
    _write(tmp_path, "config.yaml", "server:\n  port: 1\n")
    _write(
        tmp_path,
        "docs/operations.md",
        "This feature is enabled by default.\n",
    )
    result = _run(str(tmp_path), ["definition-drift"])
    d2 = [v for v in result.violations if v.rule == "D002"]
    assert [v.symbol for v in d2] == ["foo.enabled"]
    # ... and the dotted path in docs clears it
    with open(os.path.join(tmp_path, "docs", "operations.md"), "a") as fh:
        fh.write("Turn it on with `foo.enabled`.\n")
    result = _run(str(tmp_path), ["definition-drift"])
    assert not [v for v in result.violations if v.rule == "D002"]


def test_changed_files_fails_closed_outside_git(tmp_path):
    """Outside a git checkout changed_files returns None ('unknown'),
    NOT [] ('verified empty') — the CLI falls back to a full run
    instead of green-exiting on nothing."""
    assert lint_runner.changed_files(str(tmp_path)) is None


def test_changed_files_explicit_bad_base_ref_raises():
    """A typo'd --base-ref must error, not silently narrow the diff
    to the working tree (a clean tree would then lint nothing and
    pass vacuously)."""
    with pytest.raises(ValueError):
        lint_runner.changed_files(
            REPO_ROOT, base_ref="no-such-ref-xyz"
        )


def test_restricted_run_keeps_reference_corpora(taxonomy_fixture):
    """--changed-only / path restriction filters which files findings
    are REPORTED in — it must not starve cross-file checkers of their
    reference corpora.  Restricting to errors.py alone must produce
    the same errors.py findings as the full run (docs/, app.py and
    the SDK still load), not a mass E001/E004 false-positive wave."""
    full = _run(taxonomy_fixture, ["error-taxonomy"])
    restricted = _run(
        taxonomy_fixture,
        ["error-taxonomy"],
        only=["vgate_tpu/errors.py"],
    )
    assert sorted(v.fingerprint for v in restricted.violations) == (
        sorted(v.fingerprint for v in full.violations)
    )


def test_metrics_malformed_dashboard_json_is_a_finding(tmp_path):
    """A dashboard Grafana cannot parse fails the lint loudly (M004)
    without crashing the run (other findings still surface)."""
    _write(tmp_path, "monitoring/alerts.yml", "groups: []\n")
    _write(
        tmp_path,
        "monitoring/grafana-dashboard.json",
        '{"panels": [truncated',
    )
    by_name = checkers_by_name()
    result = lint_runner.run(str(tmp_path), [by_name["metrics"]])
    assert "M004" in _rules(result)


# -------------------------------------------------------- runner mechanics


def test_changed_only_scope_gating(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/server/h.py",
        "import time\n\nasync def a(r):\n    time.sleep(1)\n",
    )
    _write(tmp_path, "vgate_tpu/ops/k.py", "x = 1\n")
    # restriction set touches only ops/ -> async-blocking (scoped to
    # server/ etc.) must not run at all
    result = _run(
        str(tmp_path),
        ["async-blocking"],
        only=["vgate_tpu/ops/k.py"],
    )
    assert result.checkers_run == []
    assert result.ok
    # restriction touching server/ runs it and finds the sleep
    result = _run(
        str(tmp_path),
        ["async-blocking"],
        only=["vgate_tpu/server/h.py"],
    )
    assert result.checkers_run == ["async-blocking"]
    assert not result.ok


def test_syntax_error_is_a_finding(tmp_path):
    _write(
        tmp_path, "vgate_tpu/server/broken.py", "def f(:\n    pass\n"
    )
    result = _run(str(tmp_path), ["async-blocking"])
    assert _rules(result) == ["P001"]


def test_violations_sorted_deterministically(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/server/h.py",
        "import time\n\nasync def a(r):\n    time.sleep(1)\n"
        "\nasync def b(r):\n    time.sleep(1)\n",
    )
    r1 = _run(str(tmp_path), ["async-blocking"])
    r2 = _run(str(tmp_path), ["async-blocking"])
    assert [v.render() for v in r1.violations] == [
        v.render() for v in r2.violations
    ]
    assert [v.line for v in r1.violations] == sorted(
        v.line for v in r1.violations
    )


def test_metrics_checker_missing_monitoring_files(tmp_path):
    by_name = checkers_by_name()
    result = lint_runner.run(str(tmp_path), [by_name["metrics"]])
    rules = _rules(result)
    assert "M003" in rules  # both monitoring files absent


def test_glob_semantics():
    from vgate_tpu.analysis.core import _glob_match

    assert _glob_match("vgate_tpu/errors.py", "vgate_tpu/**/*.py")
    assert _glob_match(
        "vgate_tpu/runtime/engine_core.py", "vgate_tpu/**/*.py"
    )
    assert _glob_match("vgate_tpu/server/app.py", "vgate_tpu/server/**/*.py")
    assert not _glob_match("tests/test_api.py", "vgate_tpu/**/*.py")
    assert not _glob_match("vgate_tpu/errors.pyc", "vgate_tpu/**/*.py")
    assert _glob_match("docs/operations.md", "docs/*.md")
    assert not _glob_match("docs/sub/x.md", "docs/*.md")
